"""PPO smoke tests (reference: tests/test_algos/test_algos.py::test_ppo).

One full CLI-driven update on tiny nets against dummy/gym envs — the
integration layer of the test pyramid (SURVEY.md §4.1). Runs on the 8-device
virtual CPU mesh from conftest, so the shard_map data-parallel path is
exercised on every test.
"""

import os

import numpy as np
import pytest

from sheeprl_tpu.cli import run


def standard_args(tmp_path):
    return [
        "exp=ppo",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def test_ppo_cartpole_vector(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    assert find_checkpoints(tmp_path)


def test_ppo_host_pinned_training(tmp_path, monkeypatch):
    """algo.train_device=cpu: the whole fused update runs on the host
    backend (the remote-chip escape hatch, resolve_train_device) — full
    run + resume through the host-jitted no-mesh train path."""
    monkeypatch.chdir(tmp_path)
    args = standard_args(tmp_path) + ["fabric.devices=1", "algo.train_device=cpu"]
    run(args)
    (ckpt,) = find_checkpoints(tmp_path)
    run(args + [f"checkpoint.resume_from={ckpt}", "fabric.devices=1"])


def test_ppo_dummy_discrete_pixels(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_discrete",
            "env.screen_size=36",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
        ]
    )


def test_ppo_dummy_continuous(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_continuous",
            "env.screen_size=36",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
        ]
    )


def test_ppo_dummy_multidiscrete(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_multidiscrete",
            "env.screen_size=36",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
        ]
    )


def test_ppo_frame_stack(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_discrete",
            "env.screen_size=36",
            "env.frame_stack=2",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
        ]
    )


def test_ppo_resume_from_checkpoint(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(standard_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])


def test_ppo_resume_env_mismatch_raises(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    with pytest.raises(ValueError, match="different environment"):
        run(standard_args(tmp_path) + [f"checkpoint.resume_from={ckpt}", "env.id=Acrobot-v1"])


def test_ppo_evaluate_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])


def test_ppo_evaluate_group_override(tmp_path, monkeypatch):
    """`fabric=cpu` on the eval CLI must re-compose the fabric group (hydra
    semantics), not overwrite cfg.fabric with the bare string."""
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}", "fabric=cpu"])


def test_ppo_unknown_algo_error(tmp_path):
    with pytest.raises(ValueError, match="no registered algorithm"):
        run(standard_args(tmp_path) + ["algo.name=not_an_algo"])


def test_ppo_telemetry_smoke(tmp_path, monkeypatch):
    """One tiny CPU update with metric.telemetry.enabled=True: the run must
    leave a telemetry.jsonl whose span names match the timer metric keys and
    that carries compile/device_poll/heartbeat events, and bench.py must be
    able to compute SPS from it without log scraping (ISSUE acceptance)."""
    import json
    import sys

    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + ["metric.telemetry.enabled=True", "metric.telemetry.poll_interval=0.0"]
    )

    jsonls = []
    for root, _, files in os.walk(tmp_path):
        jsonls += [os.path.join(root, f) for f in files if f == "telemetry.jsonl"]
    assert len(jsonls) == 1, f"expected exactly one telemetry.jsonl, found {jsonls}"
    events = [json.loads(line) for line in open(jsonls[0]) if line.strip()]

    kinds = {e["event"] for e in events}
    assert {"run_start", "span", "compile", "device_poll", "heartbeat", "run_end"} <= kinds
    for e in events:
        assert {"event", "t", "step", "process_index"} <= set(e)

    # span names ARE the timer metric keys — the loop's two timed sections
    span_names = {e["name"] for e in events if e["event"] == "span"}
    assert {"Time/env_interaction_time", "Time/train_time"} <= span_names

    # bench.py digests the stream without touching the run's logs
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.path.insert(0, repo_root)
    try:
        import bench
    finally:
        sys.path.pop(0)
    summary = bench.telemetry_summary(jsonls[0])
    assert summary["sps_env"] > 0
    assert summary["sps_train"] > 0
    assert summary["compiles"] >= 1
    assert summary["device_polls"] >= 1
    hb = [e for e in events if e["event"] == "heartbeat"][-1]
    # MFU numerator: the AOT cost analysis of the fused train step landed
    assert hb.get("flops_per_train_step", 0) > 0
    assert hb.get("train_flops_per_sec", 0) > 0


def test_ppo_host_train_keeps_params_alive(tmp_path):
    """Host-pinned train path donation invariant (ISSUE satellite): the
    player aliases the params buffers, so train_fn must donate ONLY
    opt_state — after one update the old params must still be readable and
    the old opt_state must be deleted."""
    import gymnasium as gym
    import jax
    import numpy as np
    import optax

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import make_train_fn
    from sheeprl_tpu.config.compose import compose, instantiate
    from sheeprl_tpu.parallel.fabric import put_tree
    from sheeprl_tpu.utils.utils import dotdict

    cfg = dotdict(
        compose(
            "config",
            [
                "exp=ppo",
                "dry_run=True",
                "fabric.devices=1",
                "algo.rollout_steps=8",
                "algo.per_rank_batch_size=4",
                "algo.update_epochs=1",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.encoder.mlp_features_dim=8",
                "algo.encoder.cnn_features_dim=16",
                "env.num_envs=1",
                f"log_base_dir={tmp_path}/logs",
            ],
        )
    )
    fabric_cfg = dict(cfg.fabric.to_dict())
    fabric_cfg.pop("callbacks", None)
    fabric = instantiate({**fabric_cfg, "callbacks": []})
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    agent, params = build_agent(fabric, (2,), False, cfg, obs_space, None)

    host = jax.devices("cpu")[0]
    params = put_tree(jax.device_get(params), host)
    tx = optax.adam(1e-3)
    opt_state = put_tree(jax.device_get(tx.init(params)), host)
    train_fn = make_train_fn(fabric, agent, tx, cfg, ["state"], n_local=8, host_device=host)

    rng = np.random.default_rng(0)
    onehot = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=8)]
    flat = {
        "state": rng.normal(size=(8, 4)).astype(np.float32),
        "actions": onehot,
        "logprobs": np.full((8, 1), -0.7, np.float32),
        "values": np.zeros((8, 1), np.float32),
        "returns": np.ones((8, 1), np.float32),
        "advantages": rng.normal(size=(8, 1)).astype(np.float32),
    }
    new_params, new_opt_state, metrics = train_fn(
        params, opt_state, flat, jax.random.PRNGKey(0), np.float32(0.2), np.float32(0.0)
    )
    jax.block_until_ready((new_params, new_opt_state, metrics))

    # the invariant: params buffers survive the update (the host player
    # keeps serving rollouts from them) ...
    jax.tree.map(np.asarray, params)
    # ... while opt_state really was donated (the memory win stays)
    with pytest.raises(RuntimeError, match="deleted"):
        jax.tree.map(np.asarray, opt_state)
