"""Dreamer-V3 smoke tests (reference: tests/test_algos/test_algos.py::test_dreamer_v3).

One CLI-driven update with tiny nets on dummy envs, exercising the full
pipeline (rollout -> sequential buffer -> fused train step -> checkpoint ->
test) on the 8-device virtual mesh.
"""

import os

import pytest

from sheeprl_tpu.cli import run


def dv3_args(tmp_path, env_id="dummy_discrete"):
    return [
        "exp=dreamer_v3",
        "env=dummy",
        f"env.id={env_id}",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=8",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.horizon=8",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "env.screen_size=16",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


@pytest.mark.parametrize("env_id", ["dummy_discrete", "dummy_multidiscrete", "dummy_continuous"])
def test_dreamer_v3_dummy(tmp_path, monkeypatch, env_id):
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path, env_id))
    assert find_checkpoints(tmp_path)


def test_dreamer_v3_mlp_only(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        dv3_args(tmp_path)
        + ["algo.cnn_keys.encoder=[]", "algo.mlp_keys.encoder=[state]"]
    )


def test_dreamer_v3_model_axis_mesh(tmp_path, monkeypatch):
    """Full CLI run on a 2-D (data=2, model=4) mesh: params shard over the
    model axis (fabric.param_spec rule), the batch over data, GSPMD inserts
    the collectives — SURVEY §2.7 stretch scope the reference lacks."""
    monkeypatch.chdir(tmp_path)
    run(
        dv3_args(tmp_path)
        + [
            # dims divisible by model=4 so kernels genuinely split
            "algo.dense_units=16",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "fabric.mesh_axes=[data,model]",
            "fabric.mesh_shape=[2,4]",
            "algo.per_rank_batch_size=2",
        ]
    )
    assert find_checkpoints(tmp_path)


def test_dreamer_v3_fused_pallas_recurrent(tmp_path, monkeypatch):
    """Full train update through the Pallas RSSM-step kernel (interpreter
    mode on the CPU test mesh; Mosaic-compiled on a real TPU)."""
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path) + ["algo.world_model.recurrent_model.fused=pallas"])
    assert find_checkpoints(tmp_path)


def test_dreamer_v3_resume(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(dv3_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])


def test_dreamer_v3_evaluate_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])


def test_dreamer_v3_device_buffer(tmp_path, monkeypatch):
    """Full update through the HBM-resident replay ring (buffer.device=true;
    on the CPU test backend the ring lives in host memory but exercises the
    same scatter-write/gather/checkpoint code paths as on TPU)."""
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path) + ["fabric.devices=1", "buffer.device=true"])
    assert find_checkpoints(tmp_path)


def test_dreamer_v3_device_buffer_resume_across_modes(tmp_path, monkeypatch):
    """A checkpoint written by a device-ring run resumes into a host-buffer
    run and vice versa (adapt_restored_buffer)."""
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path) + ["fabric.devices=1", "buffer.device=true", "buffer.checkpoint=True"])
    (ckpt,) = find_checkpoints(tmp_path)
    # device ckpt -> host run
    run(
        dv3_args(tmp_path)
        + ["fabric.devices=1", "buffer.device=false", "buffer.checkpoint=True", f"checkpoint.resume_from={ckpt}"]
    )
    # newest ckpt (host run) -> device run
    newest = max(find_checkpoints(tmp_path), key=os.path.getmtime)
    run(
        dv3_args(tmp_path)
        + ["fabric.devices=1", "buffer.device=true", "buffer.checkpoint=True", f"checkpoint.resume_from={newest}"]
    )
