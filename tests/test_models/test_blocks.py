import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models import (
    CNN,
    MLP,
    DeCNN,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    get_activation,
)
from sheeprl_tpu.models.blocks import LayerNorm

KEY = jax.random.PRNGKey(0)


# ---- MLP (specs mirror reference tests/test_models/test_mlp.py) ----


def test_mlp_output_dim():
    m = MLP(hidden_sizes=(32, 16), output_dim=4)
    params = m.init(KEY, jnp.ones((2, 8)))
    out = m.apply(params, jnp.ones((2, 8)))
    assert out.shape == (2, 4)


def test_mlp_no_output_layer():
    m = MLP(hidden_sizes=(32, 16))
    out = m.apply(m.init(KEY, jnp.ones((2, 8))), jnp.ones((2, 8)))
    assert out.shape == (2, 16)


def test_mlp_raises_no_layers():
    m = MLP(hidden_sizes=(), output_dim=None)
    with pytest.raises(ValueError):
        m.init(KEY, jnp.ones((2, 8)))


def test_mlp_flatten_dim():
    m = MLP(hidden_sizes=(8,), flatten_dim=1)
    out = m.apply(m.init(KEY, jnp.ones((2, 4, 4))), jnp.ones((2, 4, 4)))
    assert out.shape == (2, 8)


def test_mlp_per_layer_activation_and_norm():
    m = MLP(hidden_sizes=(8, 8), activation=["relu", "tanh"], norm_layer=["layer_norm", None])
    out = m.apply(m.init(KEY, jnp.ones((2, 4))), jnp.ones((2, 4)))
    assert out.shape == (2, 8)
    # tanh output bounded
    assert np.all(np.abs(np.asarray(out)) <= 1.0)


def test_mlp_per_layer_mismatch_raises():
    m = MLP(hidden_sizes=(8, 8, 8), activation=["relu", "tanh"])
    with pytest.raises(ValueError):
        m.init(KEY, jnp.ones((2, 4)))


def test_mlp_dropout_deterministic_flag():
    m = MLP(hidden_sizes=(64,), dropout_layer=0.5)
    params = m.init(KEY, jnp.ones((2, 8)))
    out1 = m.apply(params, jnp.ones((2, 8)), deterministic=True)
    out2 = m.apply(params, jnp.ones((2, 8)), deterministic=True)
    np.testing.assert_allclose(out1, out2)
    stoch = m.apply(params, jnp.ones((2, 8)), deterministic=False, rngs={"dropout": KEY})
    assert not np.allclose(out1, stoch)


def test_mlp_bf16_compute_fp32_params():
    m = MLP(hidden_sizes=(8,), output_dim=3, dtype=jnp.bfloat16)
    params = m.init(KEY, jnp.ones((2, 4)))
    leaf = jax.tree_util.tree_leaves(params)[0]
    assert leaf.dtype == jnp.float32  # params stay fp32
    out = m.apply(params, jnp.ones((2, 4), jnp.bfloat16))
    assert out.dtype == jnp.bfloat16  # compute in bf16


# ---- CNN / DeCNN (NHWC) ----


def test_cnn_shapes_nhwc():
    m = CNN(hidden_channels=(8, 16), layer_args={"kernel_size": 3, "stride": 2, "padding": 1})
    x = jnp.ones((2, 16, 16, 3))
    out = m.apply(m.init(KEY, x), x)
    assert out.shape == (2, 4, 4, 16)


def test_cnn_matches_torch_conv_arithmetic():
    # kernel 8 stride 4 valid padding on 64x64 -> 15x15 (torch conv formula)
    m = CNN(hidden_channels=(4,), layer_args={"kernel_size": 8, "stride": 4})
    x = jnp.ones((1, 64, 64, 1))
    out = m.apply(m.init(KEY, x), x)
    assert out.shape == (1, 15, 15, 4)


def test_decnn_inverts_cnn_shape():
    # Dreamer-style: kernel 4, stride 2, padding 1 halves/doubles spatial dims
    dec = DeCNN(hidden_channels=(8,), layer_args={"kernel_size": 4, "stride": 2, "padding": 1})
    x = jnp.ones((2, 4, 4, 16))
    out = dec.apply(dec.init(KEY, x), x)
    assert out.shape == (2, 8, 8, 8)


def test_nature_cnn():
    m = NatureCNN(features_dim=512)
    x = jnp.ones((2, 64, 64, 4))
    out = m.apply(m.init(KEY, x), x)
    assert out.shape == (2, 512)
    assert np.all(np.asarray(out) >= 0)  # final relu


def test_layer_norm_dtype_preserving():
    ln = LayerNorm()
    x = jnp.ones((2, 8), jnp.bfloat16)
    out = ln.apply(ln.init(KEY, x), x)
    assert out.dtype == jnp.bfloat16


# ---- LayerNormGRUCell: math parity with the reference cell (models.py:396-403) ----


def _ref_gru_step(weight, bias, ln_scale, ln_bias, h, x, use_ln=True):
    """Numpy reimplementation of the reference LayerNormGRUCell forward."""
    joint = np.concatenate([h, x], -1)
    proj = joint @ weight + bias
    if use_ln:
        mu = proj.mean(-1, keepdims=True)
        var = proj.var(-1, keepdims=True)
        proj = (proj - mu) / np.sqrt(var + 1e-5) * ln_scale + ln_bias
    reset, cand, update = np.split(proj, 3, -1)
    reset = 1 / (1 + np.exp(-reset))
    cand = np.tanh(reset * cand)
    update = 1 / (1 + np.exp(-(update - 1)))
    return update * cand + (1 - update) * h


def test_layernorm_gru_cell_matches_reference_math():
    cell = LayerNormGRUCell(hidden_size=6, layer_norm=True)
    h = jnp.asarray(np.random.default_rng(0).normal(size=(3, 6)).astype(np.float32))
    x = jnp.asarray(np.random.default_rng(1).normal(size=(3, 4)).astype(np.float32))
    params = cell.init(KEY, h, x)
    new_h, out = cell.apply(params, h, x)
    np.testing.assert_allclose(new_h, out)

    dense = params["params"]["Dense_0"]
    ln = params["params"]["LayerNorm_0"]["LayerNorm_0"]
    expected = _ref_gru_step(
        np.asarray(dense["kernel"]),
        np.asarray(dense["bias"]),
        np.asarray(ln["scale"]),
        np.asarray(ln["bias"]),
        np.asarray(h),
        np.asarray(x),
    )
    np.testing.assert_allclose(new_h, expected, rtol=1e-4, atol=1e-5)


def test_gru_cell_scan():
    cell = LayerNormGRUCell(hidden_size=5)
    h0 = cell.initialize_carry((2,))
    xs = jnp.ones((7, 2, 3))
    params = cell.init(KEY, h0, xs[0])

    def step(h, x):
        return cell.apply(params, h, x)

    h_final, outs = jax.lax.scan(step, h0, xs)
    assert outs.shape == (7, 2, 5)
    np.testing.assert_allclose(h_final, outs[-1])


# ---- Multi encoder/decoder ----


class _DictCNN(nn.Module):
    @nn.compact
    def __call__(self, obs):
        x = obs["rgb"]
        x = CNN(hidden_channels=(4,), layer_args={"kernel_size": 3, "stride": 2, "padding": 1})(x)
        return x.reshape(*x.shape[:-3], -1)


class _DictMLP(nn.Module):
    @nn.compact
    def __call__(self, obs):
        return MLP(hidden_sizes=(6,))(obs["state"])


def test_multi_encoder_concat():
    enc = MultiEncoder(cnn_encoder=_DictCNN(), mlp_encoder=_DictMLP())
    obs = {"rgb": jnp.ones((2, 8, 8, 3)), "state": jnp.ones((2, 5))}
    out = enc.apply(enc.init(KEY, obs), obs)
    assert out.shape == (2, 4 * 4 * 4 + 6)


def test_multi_encoder_single():
    enc = MultiEncoder(mlp_encoder=_DictMLP())
    obs = {"state": jnp.ones((2, 5))}
    out = enc.apply(enc.init(KEY, obs), obs)
    assert out.shape == (2, 6)


def test_multi_encoder_requires_one():
    with pytest.raises(ValueError):
        MultiEncoder()


class _SplitDecoder(nn.Module):
    key: str
    dim: int

    @nn.compact
    def __call__(self, x):
        return {self.key: MLP(hidden_sizes=(self.dim,))(x)}


def test_multi_decoder_merges_dicts():
    dec = MultiDecoder(cnn_decoder=_SplitDecoder(key="rgb", dim=4), mlp_decoder=_SplitDecoder(key="state", dim=2))
    x = jnp.ones((2, 8))
    out = dec.apply(dec.init(KEY, x), x)
    assert set(out.keys()) == {"rgb", "state"}
    assert out["rgb"].shape == (2, 4) and out["state"].shape == (2, 2)


def test_get_activation_accepts_torch_paths():
    assert get_activation("torch.nn.SiLU") is jax.nn.silu
    assert get_activation(None)(jnp.asarray(2.0)) == 2.0
    with pytest.raises(ValueError):
        get_activation("nope")
